#!/usr/bin/env python
"""Perf-regression ledger over the BENCH_r*.json round artifacts
(ISSUE 10 tentpole): parse every round into one normalized trajectory
table (backend x config x metric x round), emit ``BENCH_LEDGER.json``
plus a markdown trend summary, and — ``--check`` — fail when the latest
round regresses a gate metric by more than the threshold against the
best prior round, so the next PR cannot silently lose PR-2/4/7's wins.

Artifact anatomy (what seven rounds actually look like):

- every round: ``{n, cmd, rc, tail?, parsed?}``;
- r06+ carry ``parsed`` = the FULL bench payload (headline keys +
  ``configs`` list + ``engines``);
- r02 carries a partial ``parsed`` (headline only) — configs recovered
  from the tail;
- r01 and r03-r05 carry only a 2000-char ``tail`` whose FRONT is
  truncated: the headline is gone, but each per-config JSON object
  (``{"config": "...", ...}``) inside is complete and recovered by a
  balanced-brace scan; the ``engines`` block names the backend;
- r01 is an error round (rc=1, TPU backend unavailable) — retained in
  the ledger as status=error with zero rows.

Comparability: rows are grouped by (backend, config, metric) — a TPU
round's numbers never gate a CPU round's (r03's device numbers are a
different machine class than the CPU-fallback trajectory). The device
backend is its own RECURRING lane (ISSUE 11): a config block may carry
its own ``backend`` string (config 12's mega-shard subprocess resolves
its platform independently of the round's), which overrides the round
backend for that config's rows, and the markdown leads with a per-lane
summary so a string of cpu rounds can never silently mask a stale or
regressed device lane — the lane table names the last round each
backend was actually measured.

Gate semantics (``--check``): only *gate metrics* fail the check —
steady/warm p50-shaped latencies and headline throughputs with a
declared better-direction (see GATE_METRICS). Everything else is
trend-reported but not gated: bench configs also carry diagnostic
columns (candidate counts, node counts, cache traffic) whose movement
is not a regression. A gate metric regresses when the LATEST round is
worse than the BEST prior same-backend round by more than
``--threshold`` (default 15%).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")
SCHEMA = 1

# The regression gates. Two kinds, chosen per metric by how the real
# r01-r07 trajectories behave:
#
# - RELATIVE gates ride the trajectory: latest round vs the BEST prior
#   same-backend round, failing beyond the threshold. Only the
#   steady/warm p50-shaped solver-path numbers qualify — they are
#   reproducible run to run. Free-run serving latencies and speedup
#   ratios swing ±20% with machine load (observed across r06→r07 on
#   unchanged code), so gating them relatively would cry wolf.
#   Host lanes (r10): wall-clock/throughput lanes additionally compare
#   only rounds measured on the same host class (the bench artifact's
#   ``host.cpus`` fingerprint) — r10 ran on a 1-core container and
#   measured the threaded serving paths ~2x slower than r09's box ON
#   UNCHANGED CODE (verified by an A/B at the r09 commit), which no
#   threshold can absorb. Quality lanes (HOST_NEUTRAL_GATES: LP gaps,
#   savings, tick counts) stay comparable across every host. Rounds
#   predating the fingerprint lane as host class "unknown".
# - ABSOLUTE gates mirror each config's published bench target (the
#   gate bench.py itself enforces): a floor for wins (pipeline speedup
#   ≥1.5x, fleet ratio ≥3x, LP saving ≥5%), a ceiling for budgets
#   (steady disruption decision ≤100 ms), and ==1.0 floors for the
#   plan-identity booleans — losing identity is always a failure.
#
# Everything else is trend-reported in the markdown but never gated:
# diagnostic counters (candidates, cache traffic, node counts) move by
# design.
RELATIVE_GATES: List[Tuple[str, str, str]] = [
    # (config, metric, direction): "down" = lower is better
    ("headline", "value", "up"),                        # pods/sec
    ("headline", "warm_ms", "down"),                    # warm solve wall
    ("config7", "warm_tick_host_ms_p50", "down"),       # PR-4 steady state
    ("config7", "noop_tick_host_ms", "down"),           # PR-4 no-op tick
    ("config7", "decision_latency_ms.p50", "down"),     # tick-driven SLO
    ("config9", "steady_decision_ms.p50", "down"),      # PR-7 steady pass
    ("config9", "churn_decision_ms.p50", "down"),       # PR-7 churn pass
    ("config10", "adversarial_saving_pct", "up"),       # PR-8 LP win
    ("config12", "mega_500k_10k_ms", "down"),           # ISSUE-11 mega-shard anchor cell
    ("config12", "mega_pods_per_sec", "up"),            # ISSUE-11 mega-shard throughput
    # ISSUE 11: the batched fleet lane gated on its OWN trajectory —
    # the ratio's solo denominator got ~50% faster (streamed catalog
    # fingerprint), so the ratio alone no longer isolates batched-lane
    # regressions
    ("config11", "batched_pods_per_sec_at_128_small", "up"),
    # ISSUE 12: the constraint-dense tensor path gated on its own wall
    # (the speedup ratio's oracle denominator is the frozen legacy
    # path, so only the tensor lane can regress it)
    ("config13", "anti_dense.tensor_ms_p50", "down"),
    ("config13", "stateful_dense.tensor_ms_p50", "down"),
    # ISSUE 13: the restored pipeline's restart lane — restore cost, the
    # first post-restart warm tick, and how many ticks to steady state.
    # Gated on their own trajectories (the speedup ratio's denominator
    # is the cold path, which other PRs legitimately speed up)
    ("config14", "restore_ms", "down"),
    ("config14", "first_tick_warm_ms", "down"),
    ("config14", "ticks_to_warm", "down"),
    # ISSUE 15: the chaos plane's latency lanes on their own
    # trajectories — the clean twin's steady p99 (lockstep rollout, a
    # reproducible solver-path shape) and the worst faulted p99 / SLO
    # burn across the five fault scenarios
    ("config15", "clean.steady_p99_ms", "down"),
    ("config15", "worst_steady_p99_ms", "down"),
    ("config15", "worst_slo_burn", "down"),
    # ISSUE 19: the optimality tier's per-shape LP gap lanes — each
    # adversarial price shape's certified gap (cost vs dual bound) on
    # its own trajectory. The gap is a pure plan-quality number (no
    # wall-clock in it), so it reproduces run to run; a widening gap
    # means refinement/branching stopped closing it. Retro-safe: the
    # metric first appears in r10, so prior rounds have no lane.
    ("config10", "per_shape_gap.bignode-trap", "down"),
    ("config10", "per_shape_gap.midsize-sweetspot", "down"),
    ("config10", "per_shape_gap.podcap-trap", "down"),
    ("config10", "per_shape_gap.hetero-split", "down"),
    ("config10", "per_shape_gap.hetero-split-narrow", "down"),
    ("config10", "per_shape_gap.hetero-split-wide", "down"),
    ("config10", "per_shape_gap.spot-cliff-steep", "down"),
    ("config10", "per_shape_gap.spot-cliff-shallow", "down"),
    ("config10", "per_shape_gap.capacity-drought", "down"),
    ("config10", "per_shape_gap.superlinear-ladder", "down"),
]
# relative gates whose numbers carry NO wall-clock: plan-quality and
# count lanes, comparable across host classes. Every other relative
# gate is host-sensitive and only compares same-host-class rounds.
HOST_NEUTRAL_GATES: frozenset = frozenset(
    [
        ("config10", "adversarial_saving_pct"),
        ("config14", "ticks_to_warm"),
    ]
    + [(cfg, m) for cfg, m, _d in RELATIVE_GATES if m.startswith("per_shape_gap.")]
)

ABSOLUTE_GATES: List[Tuple[str, str, str, float]] = [
    # (config, metric, "floor"|"ceiling", bound)
    ("config8", "steady_p99_speedup_vs_sequential", "floor", 1.5),
    ("config8", "plan_identical_all_scenarios", "floor", 1.0),
    ("config9", "steady_decision_ms.p50", "ceiling", 100.0),
    ("config9", "plan_identical_all", "floor", 1.0),
    ("config10", "adversarial_saving_pct", "floor", 5.0),
    ("config10", "lp_not_worse_all", "floor", 1.0),
    # ISSUE 19: the worst adversarial shape's certified LP gap must
    # stay under the published ceiling — the optimality tier's
    # headline promise, and an absolute bound so a future round can
    # never trade gap for speed silently
    ("config10", "opt_gap_pct_worst", "ceiling", 50.0),
    # floor re-calibrated 3.0 → 2.5 in PR 11: the solo denominator got
    # ~50% faster (streamed catalog fingerprint) with batched absolute
    # throughput unchanged — the batched lane's own trajectory is now
    # relative-gated above, so the ratio floor guards the architecture,
    # not the baseline's speed
    ("config11", "throughput_ratio_at_128_small", "floor", 2.5),
    ("config11", "plan_identical_all", "floor", 1.0),
    # ISSUE 11: sharded vs unsharded engine plan identity at subsampled
    # shapes — losing it means the mesh path stopped being memoization
    ("config12", "plan_identical_all", "floor", 1.0),
    ("config12", "plan_parity", "floor", 1.0),
    # ISSUE 12: greedy-oracle plan parity on every constraint-dense
    # cell, the covered-class oracle residue, and the published 3x
    # tensor-vs-legacy-path floor
    ("config13", "plan_parity_min", "floor", 1.0),
    ("config13", "oracle_share_max", "ceiling", 0.10),
    ("config13", "speedup_min", "floor", 3.0),
    # ISSUE 13: restart-shaped warm restore — plan identity across the
    # kill point on every cell (both resumes vs the unkilled reference),
    # the published first-solve floor, and the K=3 warm-up budget.
    # Floor raised 3.0 → 7.2 in ISSUE 17: with the managed executable
    # cache + boot jitsig replay, the restored path's first solve pays
    # neither trace nor XLA compile, so the cold/warm gap widens from
    # "restore beats re-trace" to "restore beats the whole compile"
    ("config14", "plan_identity", "floor", 1.0),
    ("config14", "first_solve_speedup", "floor", 7.2),
    ("config14", "ticks_to_warm", "ceiling", 3.0),
    # ISSUE 17: the compile-plane zero — the restored path's first solve
    # raises NO deviceplane compile events (worst warm cell across
    # seeds; boot replay re-traced every restored jitsig before tick 0)
    ("config14", "first_solve_compiles", "ceiling", 0.0),
    # ISSUE 15: chaos-plane invariants — every faulted run's plan
    # stream byte-identical to its clean twin (divergence budget 0),
    # zero plans emitted while a degradation guard held, no NodeClaim
    # write while deposed, and every holding fault actually engaged
    # its guard (a gate that never holds is proving nothing)
    ("config15", "plan_identity", "floor", 1.0),
    ("config15", "stale_plans_emitted", "ceiling", 0.0),
    ("config15", "single_writer_ok_all", "floor", 1.0),
    ("config15", "holds_engaged", "floor", 1.0),
    # ISSUE 16: the zero-recompile gates — after warmup, steady ticks
    # (config 7) and steady fleet rounds (config 11) must raise NO XLA
    # compile events (every event carries its trace_id via /debug/device)
    ("config7", "warm_tick_recompiles", "ceiling", 0.0),
    ("config11", "steady_round_recompiles", "ceiling", 0.0),
]


def _round_of(path: str) -> Optional[int]:
    m = ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


# ---------------------------------------------------------------------------
# recovery parsing


def extract_json_objects(text: str, marker: str) -> List[dict]:
    """Balanced-brace scan: every complete JSON object beginning with
    ``marker`` in ``text`` (the tail of a truncated artifact). Strings
    are respected so braces inside values cannot unbalance the scan."""
    out: List[dict] = []
    start = 0
    while True:
        i = text.find(marker, start)
        if i < 0:
            return out
        depth = 0
        in_str = False
        esc = False
        for j in range(i, len(text)):
            c = text[j]
            if in_str:
                if esc:
                    esc = False
                elif c == "\\":
                    esc = True
                elif c == '"':
                    in_str = False
                continue
            if c == '"':
                in_str = True
            elif c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
                if depth == 0:
                    try:
                        out.append(json.loads(text[i : j + 1]))
                    except ValueError:
                        pass
                    start = j + 1
                    break
        else:
            return out  # truncated object at the very end
        if start <= i:
            start = i + len(marker)


def _backend_from_tail(tail: str) -> Optional[str]:
    m = re.search(r'"engines":\s*\{[^}]*"backend":\s*"([a-z]+)"', tail)
    return m.group(1) if m else None


def parse_round(path: str) -> dict:
    """One artifact → {round, file, rc, status, backend, headline,
    configs}. status: ok | recovered | error."""
    with open(path) as f:
        doc = json.load(f)
    rnd = _round_of(path)
    rc = doc.get("rc")
    parsed = doc.get("parsed")
    tail = doc.get("tail", "") or ""
    out = {
        "round": rnd,
        "file": os.path.basename(path),
        "rc": rc,
        "status": "error",
        "backend": None,
        "host_cpus": None,
        "headline": {},
        "configs": [],
    }
    if isinstance(parsed, dict):
        out["status"] = "ok"
        out["backend"] = parsed.get("backend")
        host = parsed.get("host")
        if isinstance(host, dict) and isinstance(host.get("cpus"), int):
            out["host_cpus"] = host["cpus"]
        out["headline"] = {k: v for k, v in parsed.items() if k != "configs"}
        out["configs"] = [c for c in parsed.get("configs", []) if isinstance(c, dict)]
    if rc not in (0, None) and not out["configs"] and not out["headline"]:
        return out  # failed round, nothing recoverable
    if not out["configs"] and tail:
        # front-truncated envelope: recover the complete per-config
        # objects (and the backend) from the retained tail
        configs = [c for c in extract_json_objects(tail, '{"config"') if "config" in c]
        if configs:
            out["configs"] = configs
            if out["status"] == "error":
                out["status"] = "recovered"
        if out["backend"] is None:
            out["backend"] = _backend_from_tail(tail)
    if out["status"] == "error" and (out["configs"] or out["headline"]):
        out["status"] = "recovered"
    return out


# ---------------------------------------------------------------------------
# normalization


def config_key(cfg_name: str) -> str:
    """'2: 10k mixed ...' → 'config2'; headline rows use 'headline'."""
    m = re.match(r"\s*(\d+)\s*:", cfg_name)
    if m:
        return f"config{int(m.group(1))}"
    slug = re.sub(r"[^a-z0-9]+", "_", cfg_name.lower()).strip("_")
    return slug[:40] or "unknown"


def flatten_numeric(d: dict, prefix: str = "") -> Dict[str, float]:
    """Numeric leaves of a config block, dotted for nesting. Bools are
    counted as 0/1 (gate booleans like plan_identical_all ride along);
    strings/lists are dropped (phase breakdown lists, config names)."""
    out: Dict[str, float] = {}
    for k, v in d.items():
        name = f"{prefix}{k}"
        if isinstance(v, bool):
            out[name] = 1.0 if v else 0.0
        elif isinstance(v, (int, float)) and v is not None:
            out[name] = float(v)
        elif isinstance(v, dict):
            out.update(flatten_numeric(v, prefix=f"{name}."))
    return out


def build_table(rounds: List[dict]) -> List[dict]:
    """The normalized trajectory table: one row per
    (round, backend, config, metric)."""
    rows: List[dict] = []
    for rd in rounds:
        backend = rd.get("backend") or "unknown"
        if rd["headline"]:
            for metric, value in sorted(flatten_numeric(rd["headline"]).items()):
                rows.append(
                    {
                        "round": rd["round"],
                        "backend": backend,
                        "config": "headline",
                        "metric": metric,
                        "value": value,
                    }
                )
        for cfg in rd["configs"]:
            key = config_key(str(cfg.get("config", "")))
            # per-config backend lane (ISSUE 11): a config measured in
            # its own subprocess (config 12) resolves its platform
            # independently of the round — its rows lane by the
            # backend it actually ran on, so a cpu round can never
            # alias a device measurement (or vice versa)
            cfg_backend = cfg.get("backend")
            if not isinstance(cfg_backend, str) or not cfg_backend:
                cfg_backend = backend
            flat = flatten_numeric(
                {k: v for k, v in cfg.items() if k not in ("config", "backend")}
            )
            for metric, value in sorted(flat.items()):
                rows.append(
                    {
                        "round": rd["round"],
                        "backend": cfg_backend,
                        "config": key,
                        "metric": metric,
                        "value": value,
                    }
                )
    return rows


def trajectories(rows: List[dict]) -> Dict[Tuple[str, str, str], Dict[int, float]]:
    out: Dict[Tuple[str, str, str], Dict[int, float]] = {}
    for r in rows:
        out.setdefault((r["backend"], r["config"], r["metric"]), {})[r["round"]] = r[
            "value"
        ]
    return out


# ---------------------------------------------------------------------------
# the regression gate


def gate_direction(config: str, metric: str) -> Optional[str]:
    for cfg, m, direction in RELATIVE_GATES:
        if config == cfg and metric == m:
            return direction
    return None


def absolute_gate(config: str, metric: str) -> Optional[Tuple[str, float]]:
    for cfg, m, kind, bound in ABSOLUTE_GATES:
        if config == cfg and metric == m:
            return kind, bound
    return None


def check_regressions(
    traj: Dict[Tuple[str, str, str], Dict[int, float]],
    threshold: float,
    hosts: Optional[Dict[int, Optional[int]]] = None,
) -> List[dict]:
    """Gate pass over the trajectory table: relative gates compare the
    latest round against the best prior same-backend round; absolute
    gates hold the latest round to each config's published bench
    target. Returns the list of failures (empty = pass).

    ``hosts`` maps round → host cpu count (None = predates the
    fingerprint). When given, host-sensitive relative gates (everything
    outside HOST_NEUTRAL_GATES) only compare rounds of the same host
    class — wall-clock on a 1-core container vs a multi-core box is a
    hardware delta, not a code regression. Omitted (tests, old
    ledgers): every round is one class, prior behavior exactly."""
    failures: List[dict] = []
    for (backend, config, metric), series in sorted(traj.items()):
        latest_round = max(series)
        latest = series[latest_round]
        gate = absolute_gate(config, metric)
        if gate is not None:
            kind, bound = gate
            broken = latest < bound if kind == "floor" else latest > bound
            if broken:
                failures.append(
                    {
                        "backend": backend,
                        "config": config,
                        "metric": metric,
                        "kind": kind,
                        "latest_round": latest_round,
                        "latest": latest,
                        "bound": bound,
                        "change_pct": None,
                    }
                )
        direction = gate_direction(config, metric)
        if direction is None or len(series) < 2:
            continue
        prior = {r: v for r, v in series.items() if r != latest_round}
        if hosts is not None and (config, metric) not in HOST_NEUTRAL_GATES:
            latest_host = hosts.get(latest_round)
            prior = {r: v for r, v in prior.items() if hosts.get(r) == latest_host}
        if not prior:
            continue
        best = min(prior.values()) if direction == "down" else max(prior.values())
        if best <= 0:
            continue
        ratio = latest / best
        regressed = (
            ratio > 1.0 + threshold if direction == "down" else ratio < 1.0 - threshold
        )
        if regressed:
            failures.append(
                {
                    "backend": backend,
                    "config": config,
                    "metric": metric,
                    "kind": "relative",
                    "direction": direction,
                    "latest_round": latest_round,
                    "latest": latest,
                    "best_prior": best,
                    "best_prior_round": min(
                        (r for r, v in prior.items() if v == best), default=None
                    ),
                    "change_pct": round((ratio - 1.0) * 100.0, 2),
                }
            )
    return failures


def stale_lanes(traj: Dict[Tuple[str, str, str], Dict[int, float]]) -> List[dict]:
    """Backend lanes whose last measured round trails the newest round
    (ISSUE 16 satellite: promoted from a markdown note to a counted
    ``--check`` condition — a string of cpu rounds must not silently
    retire the device lane). Age is in rounds behind the latest."""
    lane_rounds: Dict[str, set] = {}
    for (backend, _config, _metric), series in traj.items():
        lane_rounds.setdefault(backend, set()).update(series.keys())
    if not lane_rounds:
        return []
    latest = max(max(rs) for rs in lane_rounds.values())
    out: List[dict] = []
    for backend, rs in sorted(lane_rounds.items()):
        last = max(rs)
        if last < latest:
            out.append(
                {"backend": backend, "last_round": last, "age_rounds": latest - last}
            )
    return out


def describe_failure(f: dict) -> str:
    base = f"`{f['config']}/{f['metric']}` ({f['backend']}): r{f['latest_round']:02d} = {f['latest']:g}"
    if f.get("kind") == "relative":
        return (
            base
            + f" vs best prior {f['best_prior']:g} (r{f['best_prior_round']:02d}), "
            + f"{f['change_pct']:+.1f}%"
        )
    op = "<" if f["kind"] == "floor" else ">"
    return base + f" {op} published gate {f['bound']:g}"


# ---------------------------------------------------------------------------
# emission


def write_markdown(
    path: str,
    rounds: List[dict],
    traj: Dict[Tuple[str, str, str], Dict[int, float]],
    failures: List[dict],
    threshold: float,
) -> None:
    all_rounds = sorted({rd["round"] for rd in rounds})
    lines = [
        "# Bench trajectory ledger",
        "",
        "Generated by `hack/bench_ledger.py` from the `BENCH_r*.json` round",
        "artifacts. Gate metrics compare the latest round against the best",
        f"prior same-backend round at a {threshold:.0%} threshold; wall-clock",
        "lanes additionally compare only same-host-class rounds (`host cpus`",
        "below — hardware deltas are not code regressions; quality lanes",
        "like the LP gaps stay comparable everywhere).",
        "",
        "## Rounds",
        "",
        "| round | file | status | backend | host cpus | configs |",
        "|---|---|---|---|---|---|",
    ]
    for rd in rounds:
        cpus = rd.get("host_cpus")
        lines.append(
            f"| r{rd['round']:02d} | {rd['file']} | {rd['status']} "
            f"| {rd.get('backend') or '-'} | {cpus if cpus else '?'} "
            f"| {len(rd['configs'])} |"
        )
    lane_rounds: Dict[str, set] = {}
    for (backend, _config, _metric), series in traj.items():
        lane_rounds.setdefault(backend, set()).update(series.keys())
    latest = max(all_rounds) if all_rounds else 0
    lines += [
        "",
        "## Backend lanes",
        "",
        "Each backend is its own comparison lane — relative gates only compare",
        "same-backend rounds, so a run of cpu rounds can never mask a device",
        "regression; it can only leave the device lane STALE, which this table",
        "surfaces (ISSUE 11: device rounds are meant to recur).",
        "",
        "| backend | rounds | last measured | status |",
        "|---|---|---|---|",
    ]
    for b in sorted(lane_rounds):
        rs = sorted(lane_rounds[b])
        status = (
            "current"
            if rs[-1] == latest
            else f"**STALE** ({latest - rs[-1]} round(s) behind)"
        )
        lines.append(f"| {b} | {len(rs)} | r{rs[-1]:02d} | {status} |")
    lines += ["", "## Gate-metric trends", ""]
    header = "| backend | config | metric | " + " | ".join(
        f"r{r:02d}" for r in all_rounds
    ) + " | gate |"
    lines.append(header)
    lines.append("|---" * (4 + len(all_rounds)) + "|")
    for (backend, config, metric), series in sorted(traj.items()):
        direction = gate_direction(config, metric)
        absolute = absolute_gate(config, metric)
        if direction is None and absolute is None:
            continue
        cells = [
            (f"{series[r]:g}" if r in series else "·") for r in all_rounds
        ]
        gates = []
        if direction is not None:
            gates.append("↓ better" if direction == "down" else "↑ better")
        if absolute is not None:
            kind, bound = absolute
            gates.append(f"{'≥' if kind == 'floor' else '≤'}{bound:g}")
        lines.append(
            f"| {backend} | {config} | {metric} | "
            + " | ".join(cells)
            + f" | {', '.join(gates)} |"
        )
    lines += ["", "## Check result", ""]
    if failures:
        lines.append(f"**FAIL** — {len(failures)} gate metric(s) regressed:")
        lines.append("")
        for f in failures:
            lines.append("- " + describe_failure(f))
    else:
        lines.append("**PASS** — no gate metric regressed beyond the threshold.")
    lines.append("")
    with open(path, "w") as fh:
        fh.write("\n".join(lines))


def build_ledger(bench_dir: str, threshold: float) -> dict:
    paths = sorted(
        (p for p in glob.glob(os.path.join(bench_dir, "BENCH_r*.json")) if _round_of(p)),
        key=_round_of,
    )
    rounds = [parse_round(p) for p in paths]
    rows = build_table(rounds)
    traj = trajectories(rows)
    hosts = {rd["round"]: rd.get("host_cpus") for rd in rounds}
    failures = check_regressions(traj, threshold, hosts=hosts)
    return {
        "schema": SCHEMA,
        "threshold": threshold,
        "rounds": [
            {k: rd[k] for k in ("round", "file", "rc", "status", "backend", "host_cpus")}
            | {"configs": len(rd["configs"]), "headline_metrics": len(rd["headline"])}
            for rd in rounds
        ],
        "table": rows,
        "failures": failures,
        "stale_lanes": stale_lanes(traj),
        "_rounds_full": rounds,  # stripped before writing
        "_traj": traj,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--out", default=None, help="BENCH_LEDGER.json path (default: <dir>/BENCH_LEDGER.json)")
    ap.add_argument("--md", default=None, help="markdown trend summary path (default: <dir>/BENCH_LEDGER.md)")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="gate regression threshold as a fraction (default 0.15)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when a gate metric regressed vs the best prior round")
    ap.add_argument("--allow-stale-lanes", action="store_true",
                    help="demote stale backend lanes from a --check failure to a "
                         "counted warning (ISSUE 16 satellite)")
    args = ap.parse_args(argv)

    ledger = build_ledger(args.dir, args.threshold)
    rounds = ledger.pop("_rounds_full")
    traj = ledger.pop("_traj")
    if not rounds:
        print(f"bench_ledger: no BENCH_r*.json artifacts under {args.dir}", file=sys.stderr)
        return 2

    out_path = args.out or os.path.join(args.dir, "BENCH_LEDGER.json")
    md_path = args.md or os.path.join(args.dir, "BENCH_LEDGER.md")
    with open(out_path, "w") as fh:
        json.dump(ledger, fh, indent=1, sort_keys=False)
        fh.write("\n")
    write_markdown(md_path, rounds, traj, ledger["failures"], args.threshold)

    parsed_rows = len(ledger["table"])
    print(
        f"bench_ledger: {len(rounds)} rounds, {parsed_rows} trajectory rows "
        f"→ {out_path}, {md_path}"
    )
    stale = ledger.get("stale_lanes") or []
    for s in stale:
        print(
            f"STALE LANE {s['backend']}: last measured r{s['last_round']:02d}, "
            f"{s['age_rounds']} round(s) behind the latest",
            file=sys.stderr,
        )
    rc = 0
    if ledger["failures"]:
        for f in ledger["failures"]:
            print("REGRESSION " + describe_failure(f), file=sys.stderr)
        if args.check:
            rc = 1
    if args.check and stale and not args.allow_stale_lanes:
        print(
            f"bench_ledger: {len(stale)} stale backend lane(s) — re-run the lane "
            "or pass --allow-stale-lanes to accept the gap",
            file=sys.stderr,
        )
        rc = 1
    if args.check and rc == 0:
        suffix = f" ({len(stale)} stale lane warning(s) allowed)" if stale else ""
        print(f"bench_ledger: check passed — no gate regressions{suffix}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
