#!/usr/bin/env bash
# Repo-native static analysis, CI/pre-push shape: per-file rules scope to
# the files changed vs the git merge base; project rules (the cachesound
# family) always load their configured cross-file module set, so editing
# solver.py alone still re-proves the key/read-set and generation-bump
# invariants against state/cluster.py and the provider. Pass --all for a
# full-repo run (the tier-1 meta-test shape).
#
# --telemetry (ISSUE 10): the decision-telemetry gate in one command —
# the Prometheus exposition-format checker, the bench-ledger regression
# check over the BENCH_r*.json trajectory (including the config-14
# compile-event absolute gates, ISSUE 17), the orphan-span /
# flight-recorder meta-tests, and the prewarm/compile-cache gate tests
# (zero-compile restored first solve + the witness-failure matrix).
# Tier-1 runs the same tests via pytest; this mode is the pre-push/CI
# shortcut alongside the analysis run.
#
# --concurrency (ISSUE 18): the concurrency-soundness gate in one
# command — the lock-order / wait-under-lock / process-boundary rules
# over the full repo, then the runtime lock-order witness tests and the
# mutation-kill harness. The witness instruments every inventoried
# coordination lock during the pytest session and fails teardown on any
# observed acquisition order the static graph did not predict.
#
# --config (ISSUE 20): the config-provenance & determinism gate in one
# command — the knob-inventory / knob-docs / config-provenance /
# determinism rules over the full repo, a README-vs---knobs drift check,
# then the fixture + runtime-knob-witness + mutation-kill tests. The
# witness records every KARPENTER_TPU_* env read during the pytest
# session and fails teardown on any name the static registry misses.
set -euo pipefail
cd "$(dirname "$0")/.."
if [[ "${1:-}" == "--config" ]]; then
  shift
  echo "== config rules (knob-inventory, knob-docs, config-provenance, determinism)"
  # --no-baseline: the family ships with zero grandfathered findings,
  # and a rule-scoped run must not judge other rules' entries
  python -m karpenter_core_tpu.analysis --no-baseline \
    --rules knob-inventory,knob-docs,config-provenance,determinism "$@"
  echo "== README knob table vs --knobs (drift is a byte comparison)"
  python - <<'EOF'
import sys
from karpenter_core_tpu.analysis.configprov import (
    KNOBS_BEGIN, KNOBS_END, knob_table_lines, repo_registry,
)
with open("README.md", encoding="utf-8") as f:
    text = f.read()
block = text.split(KNOBS_BEGIN, 1)[1].split(KNOBS_END, 1)[0]
documented = [ln for ln in block.splitlines() if ln.strip()]
generated = knob_table_lines(repo_registry())
if documented != generated:
    sys.exit("README knob table drifted: regenerate with "
             "`python -m karpenter_core_tpu.analysis --knobs`")
print(f"ok: {len(generated) - 2} knobs documented")
EOF
  echo "== knob witness + config-provenance mutation-kill harness"
  exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest -q -p no:cacheprovider \
    tests/test_configprov.py
fi
if [[ "${1:-}" == "--concurrency" ]]; then
  shift
  echo "== concurrency rules (lock-order, wait-under-lock, process-boundary)"
  # --no-baseline: the concurrency family ships with zero grandfathered
  # findings, and a rule-scoped run must not judge other rules' entries
  python -m karpenter_core_tpu.analysis --no-baseline \
    --rules lock-order,wait-under-lock,process-boundary "$@"
  echo "== lock-order witness + mutation-kill harness"
  exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest -q -p no:cacheprovider \
    tests/test_lockwitness.py tests/test_concurrency.py
fi
if [[ "${1:-}" == "--telemetry" ]]; then
  shift
  echo "== bench ledger --check (BENCH_r*.json trajectory gates)"
  python hack/bench_ledger.py --check "$@"
  echo "== prom-format + orphan-span + flight-recorder + prewarm gate tests"
  exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest -q -p no:cacheprovider \
    tests/test_prom_format.py tests/test_bench_ledger.py tests/test_flightrec.py \
    tests/test_prewarm.py "tests/test_tracing.py::TestOrphanAccounting"
fi
if [[ "${1:-}" == "--all" ]]; then
  shift
  exec python -m karpenter_core_tpu.analysis "$@"
fi
exec python -m karpenter_core_tpu.analysis --changed-only "$@"
