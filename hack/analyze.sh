#!/usr/bin/env bash
# Repo-native static analysis, CI/pre-push shape: per-file rules scope to
# the files changed vs the git merge base; project rules (the cachesound
# family) always load their configured cross-file module set, so editing
# solver.py alone still re-proves the key/read-set and generation-bump
# invariants against state/cluster.py and the provider. Pass --all for a
# full-repo run (the tier-1 meta-test shape).
set -euo pipefail
cd "$(dirname "$0")/.."
if [[ "${1:-}" == "--all" ]]; then
  shift
  exec python -m karpenter_core_tpu.analysis "$@"
fi
exec python -m karpenter_core_tpu.analysis --changed-only "$@"
